"""
Summarize a TPU capture directory (produced by
`scripts/capture_tpu_numbers.sh`) into one JSON object, and optionally
merge the measured numbers into `BASELINE.json`'s `"published"` map.

Usage:
    python scripts/summarize_capture.py logs/tpu-r05-20260801-093000
    python scripts/summarize_capture.py <outdir> --publish   # update BASELINE.json

Reads every `<harness>.log` in the directory, extracts the LAST JSON
result line of each (the harnesses stream partial results first — the
last line is the most complete; bench.py marks its early classic line
with a " [classic]" metric suffix), plus bitrepro's verdict object and
the integrator bench's per-(backend, B) grid rows, and prints one
combined JSON document.  `--publish` writes the per-config steps/s (and
the bitrepro verdict, and the integrator points best-value-wins) into
BASELINE.json so the measured record lives next to the target it is
judged against.
"""
import argparse
import json
import os
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]

# source of the stdlib-pure telemetry summary helpers; its own constant
# (not derived from _REPO at call time) so tests can repoint _REPO at a
# tmp BASELINE.json without losing the module
_TELEMETRY_SUMMARY_SRC = (
    Path(__file__).resolve().parents[1]
    / "magicsoup_tpu"
    / "telemetry"
    / "summary.py"
)
# source of the stdlib-pure graftpulse exposition parser, same contract
_TELEMETRY_METRICS_SRC = (
    Path(__file__).resolve().parents[1]
    / "magicsoup_tpu"
    / "telemetry"
    / "metrics.py"
)

# harness log -> key in BASELINE.json "published"
_BENCH_LOGS = {
    "bench.log": "headline_10k_128",
    "bench_40k.log": "40k_256",
    "bench_det.log": "det_10k_128",
    "bench_diffusion.log": "diffusion_10k_512",
    "bench_rich.log": "rich_10k_128",
    "bench_1k.log": "1k_128",
}


def _json_lines(path: Path) -> list[dict]:
    out = []
    if not path.exists():
        return out
    for line in path.read_text(errors="replace").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict):
            out.append(d)
    return out


def _telemetry_summary(path: Path) -> dict | None:
    """Fold a capture's graftscope ``telemetry.jsonl`` into per-phase
    p50/p95 timings and counter deltas.  Loads telemetry/summary.py by
    FILE PATH (it is stdlib-pure by contract) instead of importing
    magicsoup_tpu — summarizing a capture must not initialize a jax
    backend."""
    if not path.exists():
        return None
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_msoup_telemetry_summary", _TELEMETRY_SUMMARY_SRC
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    try:
        rows = mod.read_jsonl(path)
    except ValueError as e:
        return {"error": str(e)}
    out = mod.summarize_rows(rows)
    problems = mod.validate_rows(rows)
    if problems:
        # an invalid stream is a capture outcome, not a measurement —
        # carry WHY so publish() can refuse it
        out["error"] = "; ".join(problems[:5])
    return out


def _metrics_summary(path: Path) -> dict | None:
    """Fold a capture's final ``/metrics`` scrape (``metrics.prom``,
    written by ``performance/smoke.py --metrics`` and the serve capture
    harnesses) into the headline graftpulse numbers.  Loads
    telemetry/metrics.py by FILE PATH (stdlib-pure by contract) for the
    same no-jax reason as the telemetry fold."""
    if not path.exists():
        return None
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_msoup_telemetry_metrics", _TELEMETRY_METRICS_SRC
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    try:
        parsed = mod.parse_exposition(path.read_text(errors="replace"))
    except ValueError as e:
        # an unparseable scrape is a capture outcome, not a measurement
        return {"error": str(e)}
    return {
        "families": len(parsed["types"]),
        "device_ms_total": mod.sample_value(
            parsed, "magicsoup_device_ms_total"
        ),
        "device_dispatches_total": mod.sample_value(
            parsed, "magicsoup_device_dispatches_total"
        ),
        "megasteps_total": mod.sample_value(parsed, "magicsoup_megasteps_total"),
        "scrapes_total": mod.sample_value(parsed, "magicsoup_scrapes_total"),
        "tenant_device_ms": {
            s["labels"]["tenant"]: s["value"]
            for s in parsed["samples"]
            if s["name"] == "magicsoup_tenant_device_ms_total"
        },
    }


def summarize(outdir: Path) -> dict:
    summary: dict = {"capture_dir": str(outdir)}
    for log_name, key in _BENCH_LOGS.items():
        rows = [r for r in _json_lines(outdir / log_name) if "value" in r]
        if not rows:
            continue
        # a failed bench emits {"value": 0.0, "error": ...} — that is a
        # capture outcome, not a measurement: drop error rows whenever a
        # clean row exists (keep the last error row only when the whole
        # log failed, so the summary still shows WHY)
        clean = [r for r in rows if "error" not in r]
        if clean:
            rows = clean
        # never let an early " [classic]"-suffixed line stand in for the
        # headline: prefer the last UNSUFFIXED line; fall back to the
        # classic line only with an explicit marker so publish() skips it
        full = [
            r
            for r in rows
            if not str(r.get("metric", "")).endswith(" [classic]")
        ]
        if full:
            last = full[-1]
        else:
            last = dict(rows[-1])
            last["classic_only"] = True
        entry = {
            k: last[k]
            for k in (
                "value",
                "unit",
                "vs_baseline",
                "device_rtt_ms",
                "rtt_free_steps_per_s",
                "classic_steps_per_s",
                "pipelined_steps_per_s",
                "driver",
                "error",
                "classic_only",
            )
            if k in last
        }
        entry["metric"] = last.get("metric", "")
        summary[key] = entry
    # performance/check.py --json per-op rows (seconds, LOWER is better):
    # one entry per op, last clean row wins; error rows are skipped — a
    # failed bench is not a measurement (BENCH_r05's {"value": 0.0,
    # "error": "backend not ready"} must never enter the trend)
    check_rows = [
        r
        for r in _json_lines(outdir / "check.log")
        if "op" in r and "value" in r and "error" not in r
    ]
    if check_rows:
        ops: dict = {}
        for r in check_rows:
            ops[str(r["op"])] = r
        summary["check_ops"] = ops
    # performance/genome_ops.py rows: one seconds-per-op measurement per
    # (op, genome backend, cell count) point — keyed
    # "{op}.{backend}.{n_cells}" so the string/token pair at each size
    # stays side by side in BASELINE.json.  Same error-row rule as
    # check.log: a failed point is an outcome, not a measurement
    genome_rows = [
        r
        for r in _json_lines(outdir / "genome_ops.log")
        if "op" in r and "backend" in r and "n_cells" in r
        and "value" in r and "error" not in r
    ]
    if genome_rows:
        gops: dict = {}
        for r in genome_rows:
            gops[f"{r['op']}.{r['backend']}.{r['n_cells']}"] = r
        summary["genome_ops"] = gops
    # performance/mesh_sweep.py rows: one steps/s measurement per device
    # count (the MULTICHIP capture).  Last clean row per count wins;
    # error rows ({"error": "need 8 devices, have 1"}) are capture
    # outcomes, not measurements, and are dropped whenever any clean row
    # for that count exists
    multi_rows = [
        r
        for r in _json_lines(outdir / "multichip.log")
        if "n_devices" in r and "value" in r
    ]
    if multi_rows:
        counts: dict = {}
        for r in multi_rows:
            key = str(r["n_devices"])
            if "error" in r and "error" not in counts.get(key, {"error": 1}):
                continue  # keep an existing clean row over a later error
            counts[key] = r
        summary["multichip"] = counts
    # performance/fleet_sweep.py rows: one PER-WORLD steps/s measurement
    # per (B, K) point (the graftfleet capture).  Keyed "B{b}K{k}"; last
    # clean row per point wins, same error-row rule as multichip
    fleet_rows = [
        r
        for r in _json_lines(outdir / "fleet.log")
        if "fleet_size" in r and "megastep" in r and "value" in r
    ]
    if fleet_rows:
        points: dict = {}
        for r in fleet_rows:
            key = f"B{r['fleet_size']}K{r['megastep']}"
            if "error" in r and "error" not in points.get(key, {"error": 1}):
                continue  # keep an existing clean row over a later error
            points[key] = r
        summary["fleet"] = points
    # performance/fleet_sweep.py --mixed-rungs rows: the cross-rung
    # fusion capture.  The FUSED row per (rungs, B) point is the
    # headline (it carries "speedup" over its per-rung twin); keyed
    # "R{r}B{b}", same last-clean-row rule
    fused_rows = [
        r
        for r in _json_lines(outdir / "fleet_fused.log")
        if r.get("fused") and "rungs" in r and "value" in r
    ]
    if fused_rows:
        fpoints: dict = {}
        for r in fused_rows:
            key = f"R{r['rungs']}B{r['fleet_size']}"
            if "error" in r and "error" not in fpoints.get(key, {"error": 1}):
                continue  # keep an existing clean row over a later error
            fpoints[key] = r
        summary["fleet_fused"] = fpoints
    reps = [r for r in _json_lines(outdir / "bitrepro.log") if "result" in r]
    if reps:
        summary["bitrepro"] = reps[-1]
    integ_rows = [
        r for r in _json_lines(outdir / "integrator.log") if "ms_per_step" in r
    ]
    # grid rows carry "integrator_point" ("<backend>.B<b>"); a log from
    # an older bench has only the flat summary line, kept as fallback
    ipoints: dict = {}
    for r in integ_rows:
        key = r.get("integrator_point")
        if key is None:
            continue
        if "error" in r and "error" not in ipoints.get(key, {"error": 1}):
            continue  # keep an existing clean row over a later error
        ipoints[key] = r
    if ipoints:
        summary["integrator"] = ipoints
    elif integ_rows:
        summary["integrator"] = integ_rows[-1]
    tel = _telemetry_summary(outdir / "telemetry.jsonl")
    if tel is not None:
        summary["telemetry"] = tel
    mtx = _metrics_summary(outdir / "metrics.prom")
    if mtx is not None:
        summary["metrics"] = mtx
    return summary


def publish(summary: dict) -> None:
    baseline_path = _REPO / "BASELINE.json"
    baseline = json.loads(baseline_path.read_text())
    published = baseline.setdefault("published", {})
    merged = False
    for key in _BENCH_LOGS.values():
        entry = summary.get(key)
        # a failed or classic-only capture must never be published as a
        # headline measurement (the " [classic]" suffix / marker exists
        # precisely so the serial-loop rate cannot masquerade)
        if entry and "error" not in entry and not entry.get("classic_only"):
            # best-value-wins: the watcher re-arms across windows, and a
            # later congested window (shared tunnel, flaky RTT) must not
            # silently degrade an already-published healthy rate — these
            # are capability records, keep the fastest clean measurement.
            # ONLY when the metric string matches: a changed workload
            # (edited preset/harness) produces a different metric name
            # and must overwrite, or a stale higher number measuring a
            # different workload would be pinned forever
            prev = published.get(key)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
                and prev.get("value", 0) >= entry.get("value", 0)
            ):
                continue
            # per-entry provenance: entries from different windows can
            # coexist without misattributing one window's numbers to
            # another's capture dir
            published[key] = {**entry, "capture_dir": summary["capture_dir"]}
            merged = True
    ops = summary.get("check_ops")
    if ops:
        pub_ops = published.setdefault("check_ops", {})
        for op, entry in ops.items():
            # per-op best-value-wins with the metric-match rule of the
            # bench entries — but check rows are SECONDS per op (lower
            # is better), so "best" flips direction for unit "s"
            prev = pub_ops.get(op)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
            ):
                lower_better = entry.get("unit") == "s"
                prev_v = prev.get("value", 0)
                new_v = entry.get("value", 0)
                if (prev_v <= new_v) if lower_better else (prev_v >= new_v):
                    continue
            pub_ops[op] = {**entry, "capture_dir": summary["capture_dir"]}
            merged = True
    gops = summary.get("genome_ops")
    if gops:
        pub_gops = published.setdefault("genome_ops", {})
        for point, entry in gops.items():
            # per-(op, backend, size)-point best-value-wins; genome_ops
            # rows are seconds per op (lower is better) like check_ops,
            # with the same metric-match overwrite rule
            prev = pub_gops.get(point)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
                and prev.get("value", 0) <= entry.get("value", 0)
            ):
                continue
            pub_gops[point] = {
                **entry, "capture_dir": summary["capture_dir"]
            }
            merged = True
    multi = summary.get("multichip")
    if multi:
        pub_multi = published.setdefault("multichip", {})
        for count, entry in multi.items():
            if "error" in entry:
                continue
            # per-device-count best-value-wins (steps/s, higher is
            # better) with the same metric-match rule as the bench
            # entries: a changed sweep workload renames the metric and
            # must overwrite rather than chase a stale record
            prev = pub_multi.get(count)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
                and prev.get("value", 0) >= entry.get("value", 0)
            ):
                continue
            pub_multi[count] = {**entry, "capture_dir": summary["capture_dir"]}
            merged = True
    fleet = summary.get("fleet")
    if fleet:
        pub_fleet = published.setdefault("fleet", {})
        for point, entry in fleet.items():
            if "error" in entry:
                continue
            # per-(B,K)-point best-value-wins (per-world steps/s, higher
            # is better) with the same metric-match rule as the bench
            # entries: a changed sweep workload renames the metric and
            # must overwrite rather than chase a stale record
            prev = pub_fleet.get(point)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
                and prev.get("value", 0) >= entry.get("value", 0)
            ):
                continue
            pub_fleet[point] = {**entry, "capture_dir": summary["capture_dir"]}
            merged = True
    fused = summary.get("fleet_fused")
    if fused:
        pub_fused = published.setdefault("fleet_fused", {})
        for point, entry in fused.items():
            if "error" in entry:
                continue
            # per-(rungs,B)-point best-value-wins, same metric-match
            # rule: a changed mixed-rung workload renames the metric
            # and must overwrite rather than chase a stale record
            prev = pub_fused.get(point)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
                and prev.get("value", 0) >= entry.get("value", 0)
            ):
                continue
            pub_fused[point] = {**entry, "capture_dir": summary["capture_dir"]}
            merged = True
    tel = summary.get("telemetry")
    # per-phase dispatch timings (p50/p95) live next to check_ops: both
    # are "how long does the hot path take" records.  Unlike check_ops
    # these are whole-capture distributions, not single best numbers, so
    # best-value-wins does not apply — the last CLEAN capture's stream
    # wins wholesale (an invalid stream carries "error" and is refused,
    # same cleanliness rule as the bench entries)
    if tel and "error" not in tel and tel.get("phases"):
        published["telemetry"] = {
            "phases": tel["phases"],
            "counters": tel.get("counters", {}),
            "steps": tel.get("steps", 0),
            "dispatches": tel.get("dispatches", 0),
            "capture_dir": summary["capture_dir"],
        }
        merged = True
    integ = summary.get("integrator")
    if integ and all(
        isinstance(v, dict) and "integrator_point" in v
        for v in integ.values()
    ):
        pub_integ = published.setdefault("integrator", {})
        if not all(isinstance(v, dict) for v in pub_integ.values()):
            # a legacy flat record (pre-grid bench) can't merge with
            # per-point entries — the grid supersedes it wholesale
            pub_integ = {}
            published["integrator"] = pub_integ
        for point, entry in integ.items():
            if "error" in entry:
                continue
            # per-(backend, B)-point best-value-wins; integrator rows
            # are ms per step (LOWER is better, like check_ops seconds),
            # with the same metric-match overwrite rule as the bench
            # entries: a changed workload renames the metric and must
            # overwrite rather than chase a stale record
            prev = pub_integ.get(point)
            if (
                isinstance(prev, dict)
                and prev.get("metric") == entry.get("metric")
                and prev.get("value", 0) <= entry.get("value", 0)
            ):
                continue
            pub_integ[point] = {
                **entry, "capture_dir": summary["capture_dir"]
            }
            merged = True
    elif integ and "error" not in integ:
        # legacy flat integrator row — last clean capture wins wholesale
        published["integrator"] = {
            **integ, "capture_dir": summary["capture_dir"]
        }
        merged = True
    for key in ("bitrepro",):
        entry = summary.get(key)
        # same cleanliness rule as the bench entries: an errored verdict
        # (e.g. bitrepro's {"result": "error"} after a tunnel drop) must
        # not clobber a previous window's conclusive record
        if entry and "error" not in entry and entry.get("result") != "error":
            published[key] = {
                **entry,
                "capture_dir": summary["capture_dir"],
            }
            merged = True
    if merged:
        # atomic publish: write-then-rename so a crash (or two capture
        # windows racing) can never leave BASELINE.json truncated
        tmp_path = baseline_path.with_suffix(".json.tmp")
        tmp_path.write_text(json.dumps(baseline, indent=2) + "\n")
        os.replace(tmp_path, baseline_path)
        print(f"published -> {baseline_path}", file=sys.stderr)
    else:
        print("nothing publishable in this capture", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("outdir", type=Path)
    ap.add_argument(
        "--publish",
        action="store_true",
        help="merge the measured numbers into BASELINE.json['published']",
    )
    args = ap.parse_args()
    summary = summarize(args.outdir)
    print(json.dumps(summary, indent=2))
    if args.publish:
        publish(summary)


if __name__ == "__main__":
    main()
