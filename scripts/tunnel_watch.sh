#!/usr/bin/env bash
# Watch for the TPU tunnel to come back, then immediately run the full
# measurement capture (scripts/capture_tpu_numbers.sh) once and exit.
# The tunnel has been observed down for multi-hour stretches (see
# BENCH_NOTES.md); probing every few minutes and capturing the moment it
# returns maximizes the use of short up-windows.
#
#   bash scripts/tunnel_watch.sh [outdir] [probe_interval_s]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-logs/tpu-auto-$(date +%Y%m%d-%H%M%S)}"
INTERVAL="${2:-300}"

while true; do
    if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "$(date -Is) tunnel up — starting capture into $OUT"
        bash scripts/capture_tpu_numbers.sh "$OUT"
        exit $?
    fi
    echo "$(date -Is) tunnel down; next probe in ${INTERVAL}s"
    sleep "$INTERVAL"
done
