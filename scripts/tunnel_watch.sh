#!/usr/bin/env bash
# Watch for the TPU tunnel to come back, then immediately run the full
# measurement capture (scripts/capture_tpu_numbers.sh).  The tunnel has
# been observed down for multi-hour stretches with up-windows as short
# as minutes (see BENCH_NOTES.md).  The watch loops FOREVER: an aborted
# capture re-arms immediately with a fresh outdir, and a completed one
# re-arms after a 15-min cooldown so a later window can re-confirm the
# headline or fill configs the first window missed.  Stop it with kill.
#
#   bash scripts/tunnel_watch.sh [outdir_prefix] [probe_interval_s]
set -u
cd "$(dirname "$0")/.."
PREFIX="${1:-logs/tpu-auto}"
INTERVAL="${2:-45}"

# 75 s probe timeout + the sleep bounds worst-case window detection at
# ~2 min (a half-dead tunnel HANGS the probe; observed windows can be as
# short as ~5 min, so a 120+120 cadence could eat half a window)
n=0
while true; do
    if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        n=$((n + 1))
        OUT="$PREFIX-$(date +%Y%m%d-%H%M%S)"
        echo "$(date -Is) tunnel up — capture #$n into $OUT"
        if bash scripts/capture_tpu_numbers.sh "$OUT"; then
            echo "$(date -Is) capture complete: $OUT — re-arming after cooldown"
            # keep watching: a later window can re-confirm the headline
            # or fill configs this window missed (the summarizer merges
            # per-entry, so a partial later capture only adds).  The
            # cooldown keeps a long-lived window from being re-captured
            # back-to-back, which would just burn the chip's time.
            sleep 900
        else
            echo "$(date -Is) capture aborted (tunnel drop?); re-arming"
        fi
    else
        echo "$(date -Is) tunnel down; next probe in ${INTERVAL}s"
    fi
    sleep "$INTERVAL"
done
