#!/usr/bin/env bash
# Watch for the TPU tunnel to come back, then immediately run the full
# measurement capture (scripts/capture_tpu_numbers.sh).  The tunnel has
# been observed down for multi-hour stretches with up-windows as short
# as minutes (see BENCH_NOTES.md), so this loops until ONE capture runs
# to completion — a capture aborted by a mid-window drop re-arms the
# watch with a fresh outdir instead of giving up.
#
#   bash scripts/tunnel_watch.sh [outdir_prefix] [probe_interval_s]
set -u
cd "$(dirname "$0")/.."
PREFIX="${1:-logs/tpu-auto}"
INTERVAL="${2:-45}"

# 75 s probe timeout + the sleep bounds worst-case window detection at
# ~2 min (a half-dead tunnel HANGS the probe; observed windows can be as
# short as ~5 min, so a 120+120 cadence could eat half a window)
n=0
while true; do
    if timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        n=$((n + 1))
        OUT="$PREFIX-$(date +%Y%m%d-%H%M%S)"
        echo "$(date -Is) tunnel up — capture #$n into $OUT"
        if bash scripts/capture_tpu_numbers.sh "$OUT"; then
            echo "$(date -Is) capture complete: $OUT"
            exit 0
        fi
        echo "$(date -Is) capture aborted (tunnel drop?); re-arming"
    else
        echo "$(date -Is) tunnel down; next probe in ${INTERVAL}s"
    fi
    sleep "$INTERVAL"
done
