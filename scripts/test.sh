#!/usr/bin/env bash
# Run the test suite: `bash scripts/test.sh` (fast tier) or
# `bash scripts/test.sh tests/` (everything, incl. slow invariants).
set -euo pipefail
cd "$(dirname "$0")/.."
TARGET="${1:-tests/fast}"
# graftlint gate first: the static analyzer is cheap (stdlib AST, no jax
# import) and a hot-path violation should fail before the suite spends
# minutes compiling.  The SARIF artifact is the machine-readable copy of
# the same run — what a CI code-scanning upload step would ingest
python -m magicsoup_tpu.analysis --check --sarif graftlint.sarif
# arm the graftrace runtime ownership assertions (analysis/ownership.py)
# for the whole suite: every test doubles as a thread-ownership probe of
# the serve loop, stepper workers, telemetry flush, and signal handlers;
# production runs leave the flag unset and pay nothing
export MAGICSOUP_DEBUG_OWNERSHIP=1
# the unit tier includes the graftcheck property-based suite
# (tests/fast/test_check_properties.py): under Hypothesis it runs a
# bounded CI profile (max_examples + deadline capped); without it the
# same properties run over fixed seeded samples — gating either way
python -m pytest "$TARGET" -q
# steps/s smoke: prove the pipelined dispatch->replay->flush path end to
# end and leave a throughput number in the CI log (JSON, no threshold —
# see performance/smoke.py).  Its second JSON line is the phenotype-cache
# effectiveness gate: a duplicate-genome burst must hit the cache and
# stay bit-identical to a cache-disabled world; its third is the
# graftscope telemetry gate: the run's JSONL stream must validate
# (schema + monotone counters) and `python -m magicsoup_tpu.telemetry
# summarize` must accept it (exits nonzero otherwise)
python performance/smoke.py
# sharded stepper smoke (GATING): a 2-forced-host-device det-mode mesh
# trajectory must be BIT-identical to the single-device det trajectory
# (both run in one child process — see performance/mesh_sweep.py --check);
# exits nonzero on any byte difference
python performance/mesh_sweep.py --check --devices 2 \
    --n-cells 24 --map-size 16 --genome-size 200 --steps 4
# graftguard chaos smoke (GATING): SIGKILL a det-mode child mid-megastep
# and resume it from its crash-safe checkpoint — the final state must be
# BIT-identical to the uninterrupted run; also flips checkpoint bytes
# (typed rejection + retention fallback), SIGTERMs a child (graceful
# drain -> final checkpoint + flushed telemetry), trips the NaN
# sentinel / transient-dispatch retry, and runs the graftcheck deep
# audit post-resume (must pass clean, must reject seeded corruptions).
# Also SIGKILLs a B=2 FLEET child after an atomic fleet checkpoint and
# resumes it — the resumed fleet digest must equal the uninterrupted
# baseline's.  Exits nonzero on any violation.
python performance/smoke.py --chaos
# graftfleet smoke (GATING): B=3 det-mode worlds across two capacity
# rungs stepped as a fleet — the warm steady state must pass
# hot_path_guard(compile_budget=0), the fetch census must show exactly
# ONE host fetch per rung group per megastep (no per-world D2H), and
# the batched telemetry must validate with per-world fleet_slot /
# fleet_size lanes on every dispatch row.  Exits nonzero on any
# violation.
python performance/smoke.py --fleet
# cross-rung fused dispatch smoke (GATING): B=4 det-mode worlds across
# two capacity rungs under fusion="fleet" — the warm steady state must
# pass hot_path_guard(compile_budget=0) while the runtime.snapshot()
# censuses count exactly ONE device dispatch + ONE physical fetch per
# megastep for the WHOLE fleet (fused_groups bills both rungs into the
# single launch).  Exits nonzero on any violation.
python performance/smoke.py --fused
# device-resident-genome smoke (GATING): a token-backed and a
# string-backed det-mode world drive the same seeded
# mutate -> recombinate -> translate -> divide schedule (the string
# side REPLAYS the token kernels at the token store's exact (cap, G)
# shape) — every boundary digest must be BIT-identical across
# backends, the token store must pass check.audit_world, and a
# token-backed pipelined steady state must hold
# hot_path_guard(compile_budget=0) with ZERO host genome decodes.
# Exits nonzero on any violation.
python performance/smoke.py --genome
# graftwarden fault-isolation smoke (GATING): a B=3 det fleet under
# policy="heal" has one world NaN-poisoned mid-run — only that world
# may be evicted, it must heal from its own rolling checkpoint stream,
# the two healthy worlds' digests must stay BIT-identical to an
# identically-cadenced unpoisoned baseline, the poisoned lane's
# telemetry must validate with the quarantine -> heal warden events,
# and an armed (untripped) warden must leave the fetch census and
# compile census unchanged.  Exits nonzero on any violation.
python performance/smoke.py --fleet-chaos
# graftcheck differential smoke (GATING): one seeded
# spawn/step/mutate/kill/divide/compact schedule through the classic
# driver, the stepper at K=1 and K=4, and a 2-tile mesh — all four
# det-mode trajectories must produce identical per-boundary state
# digests (magicsoup_tpu/check/differential.py).  Exits nonzero on any
# divergence.
python performance/smoke.py --differential
# graftserve multi-tenant smoke (GATING): loopback `python -m
# magicsoup_tpu.serve` children driven over HTTP — warm-rung admission
# must serve a fourth tenant under compile_budget=0 with ZERO new
# compiles (cold spec -> 429), the fetch census must show exactly one
# physical fetch per rung-group step, the accounting rows must sum
# exactly to the steps served and fetch bytes observed, SIGTERM must
# drain into final checkpoints + a registry and exit 0, and a SIGKILLed
# service restarted on the same directory must re-adopt every tenant
# and finish the schedule with digests BIT-identical to the
# uninterrupted baseline's.  Exits nonzero on any violation.
python performance/smoke.py --serve
# graftpulse live-metrics smoke (GATING): a loopback serve child is
# double-scraped over HTTP — GET /metrics must return exposition-format
# 0.0.4 text under the pinned content type, every counter family must
# be monotone across the scrapes, the per-tenant device_ms series must
# sum exactly to the accounting rows' device_us bill (itself conserved
# against total_device_us), a warm steady-state megastep between the
# scrapes must compile ZERO new programs with metrics armed, and
# /healthz must carry the live queue_depth / oldest_command_age_s
# fields.  Exits nonzero on any violation.
python performance/smoke.py --metrics
# integrator-backend smoke (GATING): a World(integrator="pallas")
# pipelined run with the kernel in interpret mode — the warm steady
# state must hold hot_path_guard(compile_budget=0), the fetch census
# must count exactly ONE host fetch per megastep, the runtime
# integrator census must bill every megastep to the pallas backend
# (ops/backends.py registry routing, not a bypass), and the final
# world must pass check.audit_world.  Exits nonzero on any violation.
python performance/smoke.py --pallas
# graftchaos campaign gate (GATING): the fast subset of the chaos
# matrix (performance/chaos_matrix.py) — checkpoint ENOSPC mid-save
# (counted, next save lands, no torn file), torn-write walk-back,
# checkpoint-read EIO (typed CheckpointError check="io"), a transient
# dispatch fault under a FUSED mixed-rung launch (absorbed, every
# co-fused tenant bit-identical), and the serve command queue
# rejecting with 503 + Retry-After — each cell in a
# timeout-bounded child process, each required to terminate in exactly
# its contract state (recovered | degraded | raised).  Exits nonzero on
# any contract violation; the full 16-cell matrix runs with no flag.
python performance/chaos_matrix.py --gate
