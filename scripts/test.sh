#!/usr/bin/env bash
# Run the test suite: `bash scripts/test.sh` (fast tier) or
# `bash scripts/test.sh tests/` (everything, incl. slow invariants).
set -euo pipefail
cd "$(dirname "$0")/.."
TARGET="${1:-tests/fast}"
python -m pytest "$TARGET" -q
