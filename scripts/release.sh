#!/usr/bin/env bash
#
# Tag-driven release (counterpart of the reference's scripts/release.sh):
# verifies the version is consistent and the tree is clean, builds the
# distributables locally as a smoke test, then pushes the tag — CI's
# wheel job does the authoritative build on the tag.
#
#   bash scripts/release.sh
#
set -euo pipefail
cd "$(dirname "$0")/.."

py_version=$(grep '^version = ' pyproject.toml | sed 's/version = //; s/"//g')
init_version=$(grep '^__version__' magicsoup_tpu/__init__.py | sed 's/.*"\(.*\)"/\1/')

if [[ "$py_version" != "$init_version" ]]; then
    echo "version mismatch: pyproject.toml=$py_version __init__.py=$init_version" >&2
    exit 1
fi
if [[ -n "$(git status --porcelain)" ]]; then
    echo "working tree not clean; commit first" >&2
    exit 1
fi
if git rev-parse "v$py_version" >/dev/null 2>&1; then
    echo "tag v$py_version already exists" >&2
    exit 1
fi

echo "local build smoke test (sdist + wheel)"
python -m build

read -r -p "Release as v${py_version}? (y/N) " confirm
[[ $confirm == [yY] || $confirm == [yY][eE][sS] ]] || exit 1

git tag "v$py_version"
git push origin "v$py_version"
echo "pushed v$py_version — CI builds and uploads the artifacts"
