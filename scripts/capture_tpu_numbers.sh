#!/usr/bin/env bash
# One-shot TPU measurement capture: runs every performance harness
# sequentially (NEVER in parallel — concurrent jobs contaminate each
# other's timings through the shared chip and tunnel, see
# performance/README.md) and tees the results into logs/.
#
#   bash scripts/capture_tpu_numbers.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-logs/tpu-$(date +%Y%m%d-%H%M%S)}"
mkdir -p "$OUT"

echo "== backend probe" | tee "$OUT/capture.log"
if ! timeout 120 python -c "import jax; print(jax.devices())" >>"$OUT/capture.log" 2>&1; then
    echo "backend unreachable; aborting" | tee -a "$OUT/capture.log"
    exit 1
fi

run() {
    name="$1"; shift
    echo "== $name: $*" | tee -a "$OUT/capture.log"
    timeout 1800 "$@" >"$OUT/$name.log" 2>&1
    echo "rc=$? (tail)" | tee -a "$OUT/capture.log"
    tail -5 "$OUT/$name.log" | tee -a "$OUT/capture.log"
}

run bench          python bench.py
run bench_40k      python bench.py --config 40k --warmup 4 --steps 8
run bench_diffusion python bench.py --config diffusion --warmup 4 --steps 8
run bench_det      python bench.py --det --warmup 4 --steps 8
run profile_step   python performance/profile_step.py --n-cells 10000 --warmup 6 --steps 12
run integrator     python performance/integrator_bench.py
run check          python performance/check.py

echo "done; logs in $OUT" | tee -a "$OUT/capture.log"
