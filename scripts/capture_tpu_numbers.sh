#!/usr/bin/env bash
# One-shot TPU measurement capture: runs every performance harness
# sequentially (NEVER in parallel — concurrent jobs contaminate each
# other's timings through the shared chip and tunnel, see
# performance/README.md) and tees the results into logs/.
#
# Ordered most-valuable-first: tunnel up-windows have been observed as
# short as ~5 minutes, so the headline bench, the integrator
# microbenchmark and the Pallas lowering ladder come before the wider
# shape sweeps.  If the backend stops responding between harnesses the
# capture exits nonzero immediately instead of burning the window on
# retries — scripts/tunnel_watch.sh then re-arms for the next window.
#
#   bash scripts/capture_tpu_numbers.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-logs/tpu-$(date +%Y%m%d-%H%M%S)}"
mkdir -p "$OUT"

# publish whatever WAS measured even when a mid-capture tunnel drop
# aborts the run partway (observed windows can be ~5 min): the trap
# fires on every exit path; the summarizer itself refuses errored /
# classic-only lines, so partial captures only contribute clean numbers
trap 'python scripts/summarize_capture.py "$OUT" --publish \
    > "$OUT/summary.json" 2>>"$OUT/capture.log" || true' EXIT

# bounded retries AND a bounded single attempt: a mid-capture tunnel
# drop (or a half-dead hang inside one bench child) should fail fast
# here and hand control back to the watcher, not poll for 30 minutes
# per harness.  600 s per attempt leaves room for a cold-cache compile
# warmup, and the retry budget EXCEEDS it so a first attempt killed at
# the timeout (its compiles persist in the cache) still gets one fast
# retry — a budget below the attempt timeout can never retry at all.
export MAGICSOUP_BENCH_RETRY_BUDGET="${MAGICSOUP_BENCH_RETRY_BUDGET:-900}"
export MAGICSOUP_BENCH_ATTEMPT_TIMEOUT="${MAGICSOUP_BENCH_ATTEMPT_TIMEOUT:-600}"
# line-buffered stdout: the per-harness logs are pipes/files, and a
# timeout-kill must not erase numbers a harness already printed
export PYTHONUNBUFFERED=1

# Hard wall-clock watchdog around the probe: the documented hang mode is
# a jax.devices() that wedges inside the C++ client, which a plain
# `timeout` SIGTERM cannot always kill — `-k 10` escalates to SIGKILL.
# A failed/hung probe leaves a structured JSON record in the capture dir
# (the {"value": 0.0, "error": ...} shape summarize_capture.py already
# skips) so the published summary names WHY the window died instead of
# silently missing rows.
probe() {
    timeout -k 10 120 python -c "import jax; print(jax.devices())" \
        >>"$OUT/capture.log" 2>&1
    rc=$?
    if [ "$rc" -ne 0 ]; then
        reason="probe exited rc=$rc"
        if [ "$rc" -ge 124 ]; then
            reason="probe hung past 120s watchdog (rc=$rc)"
        fi
        printf '{"metric": "backend probe", "value": 0.0, "error": "%s"}\n' \
            "$reason" >>"$OUT/probe.log"
    fi
    return $rc
}

echo "== backend probe" | tee "$OUT/capture.log"
if ! probe; then
    echo "backend unreachable; aborting" | tee -a "$OUT/capture.log"
    exit 1
fi

# run <name> <timeout_s> <cmd...>: per-harness hard timeout (the bench.py
# runs ALSO bound themselves via the env vars above; the other harnesses
# have no internal retry loop, so this cap is their only fail-fast).
# Non-bench harnesses take the shared accelerator flock (bench.py locks
# itself) so a driver-initiated benchmark in the same window serializes
# instead of contending through the one chip+tunnel; -w 300 bounds the
# wait so a long-held lock costs one harness slot, not the capture.
# Keep in sync with _ACCEL_LOCK_PATH in bench.py (including its
# MAGICSOUP_BENCH_LOCK_PATH override, or the two sides stop excluding
# each other).
LOCK="${MAGICSOUP_BENCH_LOCK_PATH:-/tmp/magicsoup_tpu_accel.lock}"
run() {
    name="$1"; to="$2"; shift 2
    echo "== $name (<=${to}s): $*" | tee -a "$OUT/capture.log"
    # every harness serializes on the one flock; MAGICSOUP_BENCH_LOCK_HELD
    # tells bench.py's own _acquire_accel_lock the lock is already held
    # around it (no self-deadlock, and no fragile command-string matching
    # to decide which harnesses lock themselves)
    timeout "$to" flock -w 300 "$LOCK" \
        env MAGICSOUP_BENCH_LOCK_HELD=1 "$@" >"$OUT/$name.log" 2>&1
    rc=$?
    echo "rc=$rc (tail)" | tee -a "$OUT/capture.log"
    tail -5 "$OUT/$name.log" | tee -a "$OUT/capture.log"
    if [ "$rc" -ne 0 ] && ! probe; then
        echo "backend lost after $name; aborting capture" \
            | tee -a "$OUT/capture.log"
        exit 1
    fi
}

# VERDICT r03 priority order: headline (pipelined vs classic), integrator
# latency, bit-repro re-pin at HEAD (cheap, must share the bench's
# window+commit), then the 40k/det/diffusion preset validations, then the
# Mosaic ladder and wider sweeps.
run bench           1800 python bench.py
# backend x B grid: xla-fast vs the batched 2D-grid pallas kernel at
# B in {1,4} — one JSON row per point for published["integrator"]
run integrator       900 python performance/integrator_bench.py --backend xla-fast,pallas --fleet-b 1,4
# 1800 s: a DIVERGING bitrepro re-runs both children to quantify ULP
# magnitudes (scripts/bitrepro.py _divergence_magnitudes), roughly
# doubling its runtime — and a conclusive divergence verdict is worth
# more than the harnesses behind it in the queue
run bitrepro        1800 python scripts/bitrepro.py
run bench_40k       1800 python bench.py --config 40k --warmup 4 --steps 8
run bench_det       1800 python bench.py --det --warmup 4 --steps 8
run bench_rich      1800 python bench.py --config rich --warmup 4 --steps 8
run bench_1k        1200 python bench.py --n-cells 1000 --warmup 4 --steps 10
run pallas_bisect   1500 python performance/pallas_bisect.py
run profile_step     900 python performance/profile_step.py --n-cells 10000 --warmup 6 --steps 12
run bench_diffusion 1800 python bench.py --config diffusion --warmup 4 --steps 8
# real per-device-count throughput rows (steps/s at n_devices 1/2/4/8),
# not an rc/ok smoke: each count runs in its own child process (the
# device inventory is fixed at backend init) and prints one JSON line
# that summarize_capture publishes under published["multichip"].
# --platform '' lets the child take real TPU chips when present.
run multichip       1800 python performance/mesh_sweep.py --devices 1,2,4,8 --platform ''
# per-world throughput across fleet sizes (B x K grid): one JSON line
# per point that summarize_capture publishes under published["fleet"].
# The B=1 vs B=16 per-world ratio IS the dispatch-amortization number
# the graftfleet batch axis exists for.
run fleet           1800 python performance/fleet_sweep.py --platform ''
run fleet_fused     1800 python performance/fleet_sweep.py --mixed-rungs --bs 1,4,16 --platform ''
run check           1200 python performance/check.py
# string engine vs device token kernels per (op, backend, size): one
# JSON row per point that summarize_capture publishes under
# published["genome_ops"] — the mutate/update >=3x-at-8k gate of the
# device-resident-genome work is judged from THIS capture's token rows
# (BENCH_NOTES.md: on XLA:CPU the dense-PRNG kernels lose to the
# O(#mutations) host engine; the win is an accelerator lever)
run genome_ops      1200 python performance/genome_ops.py --json

echo "done; logs in $OUT" | tee -a "$OUT/capture.log"
# (summarize + publish runs in the EXIT trap above, on success AND abort)
