"""
Headline benchmark: sim steps/sec at 10k cells on a 128x128 map running the
reference's realistic workload (`performance/run_simulation.py:43-113`):
spawn top-up, enzymatic_activity, ATP-threshold kill and divide,
recombinate, mutate, degrade+diffuse+lifetimes.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "steps/s", "vs_baseline": N}

Baseline: the reference's CUDA numbers (EC2 GPU, 2023-12-19,
`performance/run_simulation.py:20`) are 0.03 s/step at 1k cells and
0.30 s/step at 40k cells; linear interpolation in cell count gives
~0.0923 s/step at 10k cells -> 10.83 steps/s.  `vs_baseline` > 1 means
faster than the reference on its own headline workload.

Run on whatever accelerator JAX finds (the driver provides a TPU chip); do
not pin a platform here.

Robustness: the accelerator is reached over a tunnel that can drop.  The
parent process never imports jax; it runs the real measurement in child
processes with bounded retry/backoff (MAGICSOUP_BENCH_RETRY_BUDGET seconds
total, default 1200 — deliberately well under the driver's ~30 min kill
window).  There is NO separate backend probe on the critical path: attempt
#1 IS the measurement, guarded by a backend-ready watchdog — the child
prints a ready marker the moment `jax.devices()` answers, and a child that
shows no sign of life within MAGICSOUP_BENCH_READY_TIMEOUT seconds
(default 90; a half-dead tunnel hangs forever there) is killed and
retried.  In a short tunnel window this makes the first number land
within ~90 s of a healthy backend appearing instead of after a probe
round-trip.  Result lines are forwarded to stdout the moment the child
prints them (the classic-loop number — metric suffixed " [classic]" so it
can never be mistaken for the headline — is printed before the pipelined
bench starts), so a later hang or kill cannot erase an already-measured
number.  If the child dies after the classic line but before the headline
line, the parent retries once (compiles are cached, so the retry is
cheap).  If every attempt fails, it still prints one parseable JSON line
with an "error" field instead of dying with a traceback — including when
the driver SIGTERMs it.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

def baseline_s_per_step(n_cells: int) -> float:
    """The reference's measured CUDA seconds/step as a function of cell
    count: 0.03 at 1k and 0.30 at 40k cells (both direct measurements,
    `performance/run_simulation.py:20`), linearly interpolated between —
    10k cells gives ~0.0923 s/step.  Map size is not in the reference's
    numbers (both its measurements ran 256^2); treat vs_baseline at other
    map sizes as indicative only."""
    frac = (n_cells - 1_000) / (40_000 - 1_000)
    return 0.03 + (0.30 - 0.03) * frac


BASELINE_S_PER_STEP = baseline_s_per_step(10_000)

# named shape presets: the headline, the reference's second headline
# (40k cells / 256^2 map), the diffusion-heavy BASELINE.json config, and
# the rich-chemistry config (co2_fixing: 41 molecules / 46 reactions,
# multi-domain proteins — the closest example module to BASELINE.json's
# "32 molecules / 64 reactions" spec)
CONFIGS = {
    "headline": {"n_cells": 10_000, "map_size": 128},
    "40k": {"n_cells": 40_000, "map_size": 256},
    "diffusion": {"n_cells": 10_000, "map_size": 512},
    "rich": {"n_cells": 10_000, "map_size": 128, "chemistry": "co2_fixing"},
}

# chemistry modules by name; imported lazily in the child because the
# interned Molecule registry forbids two example chemistries that share
# molecule names (with different attributes) in one process
_CHEMISTRIES = ("wood_ljungdahl", "co2_fixing")

# optional platform pin for CPU smoke tests of this harness (the real
# bench runs on whatever the driver provides and leaves this unset)
_PLATFORM = os.environ.get("MAGICSOUP_BENCH_PLATFORM", "")


def apply_platform_pin(jax_module) -> None:
    """Apply the MAGICSOUP_BENCH_PLATFORM pin (shared by every harness —
    bench, profile_step, integrator_bench — so the env-var contract has
    exactly one implementation).  The axon TPU plugin ignores
    JAX_PLATFORMS, so a config-level pin is the only way to force CPU."""
    if _PLATFORM:
        jax_module.config.update("jax_platforms", _PLATFORM)


def probe_backend(timeout_s: float) -> tuple[bool, str]:
    """Subprocess probe with a hard timeout, honoring the platform pin —
    for harnesses without their own retry/watchdog machinery (bench.py
    itself does not probe: its measurement child doubles as one).  A
    half-dead tunnel hangs in-process backend init forever, which is why
    this must be a killable subprocess."""
    code = "import jax; jax.devices()"
    if _PLATFORM:
        code = (
            "import jax; "
            f"jax.config.update('jax_platforms', {_PLATFORM!r}); "
            "jax.devices()"
        )
    # own session so a hung probe (plus any runtime helpers it spawned)
    # can be killed as a whole process group — subprocess.run's timeout
    # kill only reaches the direct child and leaks its orphans
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        _, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"backend probe hung (> {timeout_s:.0f}s)"
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
    if proc.returncode != 0:
        return False, (stderr or "")[-2000:]
    return True, ""

# stderr markers that indicate a transient backend/tunnel failure worth retrying
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Unable to initialize backend",
    "backend setup/compile error",
    "Connection reset",
    "Connection refused",
    "Broken pipe",
    "Socket closed",
)


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config",
        choices=sorted(CONFIGS),
        default=None,
        help="named shape preset; fills in any of --n-cells/--map-size/"
        "--chemistry not passed explicitly (explicit flags win)",
    )
    # preset-controlled args default to None so an EXPLICIT value — even
    # one equal to the fallback — is distinguishable and always wins
    # over a --config preset; _apply_config fills the rest
    ap.add_argument("--n-cells", type=int, default=None)
    ap.add_argument("--map-size", type=int, default=None)
    ap.add_argument(
        "--chemistry",
        choices=_CHEMISTRIES,
        default=None,
        help="example chemistry module driving the workload",
    )
    ap.add_argument("--genome-size", type=int, default=500)
    ap.add_argument("--warmup", type=int, default=8)
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--pallas",
        action="store_true",
        help="use the VMEM-tiled Pallas integrator kernel",
    )
    ap.add_argument(
        "--det",
        action="store_true",
        help="run in the deterministic (bit-reproducible) numeric mode",
    )
    ap.add_argument(
        "--classic",
        action="store_true",
        help="measure only the classic serial loop (skip the pipelined driver)",
    )
    ap.add_argument(
        "--lag",
        default="auto",
        help="pipeline depth for the pipelined driver: 'auto' or an int",
    )
    ap.add_argument(
        "--megastep",
        type=int,
        default=1,
        help="fused device steps per dispatch (K) for the pipelined "
        "driver; spawn/selection replay granularity becomes K steps "
        "and the host lag is lag x K steps",
    )
    ap.add_argument(
        "--_child",
        action="store_true",
        help=argparse.SUPPRESS,  # internal: actually run the measurement
    )
    return ap


def _setup_compile_cache(jax) -> None:
    """Persistent compile cache: pad-size variants recompile across
    invocations otherwise (expensive through a remote compile service).
    Delegates to the library helper (magicsoup_tpu/cache.py) so bench,
    performance harnesses, the stepper's warm scheduler, and tests all
    share one env-overridable cache location; the ``jax`` parameter is
    kept for import compatibility."""
    del jax  # the helper imports jax itself (lazily)
    from magicsoup_tpu.cache import ensure_compile_cache

    ensure_compile_cache()


def _child_main(args: argparse.Namespace) -> None:
    """The real measurement; runs in a subprocess so a backend hang or
    init failure never poisons the parent's retry loop."""
    import random

    if args.det:
        # the numeric mode is read from the env when a World is built
        os.environ["MAGICSOUP_TPU_DETERMINISTIC"] = "1"

    import jax

    apply_platform_pin(jax)
    _setup_compile_cache(jax)

    # ready marker: the parent's watchdog kills a child that never gets
    # here (a half-dead tunnel hangs forever inside jax.devices()); once
    # this line is out, only the full attempt timeout applies
    devs = jax.devices()
    sys.stderr.write(
        f"[bench-child] backend ready: {len(devs)} {devs[0].platform} device(s)\n"
    )
    sys.stderr.flush()

    import importlib

    import magicsoup_tpu as ms
    from magicsoup_tpu.util import random_genome

    CHEMISTRY = importlib.import_module(
        f"magicsoup_tpu.examples.{args.chemistry}"
    ).CHEMISTRY

    sys.path.insert(0, str(Path(__file__).resolve().parent / "performance"))
    from workload import sim_step

    rng = random.Random(args.seed)
    world = ms.World(
        chemistry=CHEMISTRY,
        map_size=args.map_size,
        seed=args.seed,
        integrator="pallas" if args.pallas else None,
    )
    world.spawn_cells(
        [random_genome(s=args.genome_size, rng=rng) for _ in range(args.n_cells)]
    )
    atp = CHEMISTRY.molname_2_idx["ATP"]

    def step(sync: bool) -> None:
        sim_step(
            world,
            rng,
            n_cells=args.n_cells,
            genome_size=args.genome_size,
            atp_idx=atp,
            sync=sync,
        )

    import statistics

    for _ in range(args.warmup):
        step(sync=True)
    # the warmup steps auto-schedule background compile warmers one
    # row-ladder rung ahead; settle them so no remote compile can land
    # inside the measured window
    world.wait_warm()

    # measure the tunnel/device round-trip latency: the workload has one
    # mandatory device->host fetch per step (the selection threshold), so
    # on remote accelerators this bounds steps/s at 1/rtt regardless of
    # compute; report it so the headline number can be interpreted
    import jax.numpy as jnp

    z = jnp.zeros((), jnp.float32)
    float(z)
    rtts = []
    for _ in range(9):
        t0 = time.perf_counter()
        float(z + 1.0)
        rtts.append(time.perf_counter() - t0)
    rtt_ms = statistics.median(rtts) * 1e3

    t0 = time.perf_counter()
    for _ in range(args.steps):
        # async steps: each step's selection fetch syncs the prior one
        step(sync=False)
    # true barrier: a VALUE fetch (block_until_ready can ack early on
    # remote-tunneled backends)
    float(world._molecule_map[0, 0, 0])
    float(world._cell_molecules[0, 0])
    dt = dt_classic = (time.perf_counter() - t0) / args.steps

    mode = " [deterministic]" if args.det else (" [pallas]" if args.pallas else "")
    metric_name = (
        f"sim steps/sec ({args.n_cells} cells, "
        f"{args.map_size}x{args.map_size} map, "
        f"{args.chemistry.replace('_', '-')} "
        f"run_simulation workload){mode}"
    )

    def emit(steps_per_s: float, metric: str = metric_name, **fields) -> None:
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": round(steps_per_s, 4),
                    "unit": "steps/s",
                    "vs_baseline": round(
                        steps_per_s * baseline_s_per_step(args.n_cells), 4
                    ),
                    "device_rtt_ms": round(rtt_ms, 1),
                    # the serial loop's throughput with its one per-step
                    # fetch subtracted — the co-located-hardware proxy the
                    # pipelined driver is judged against
                    "rtt_free_steps_per_s": round(
                        1.0 / max(dt_classic - rtt_ms / 1e3, 1e-9), 4
                    ),
                    **fields,
                }
            ),
            flush=True,
        )

    # print the classic number the moment it exists: a hang or kill later
    # in the pipelined bench must not erase an already-measured result
    # (the parent forwards this line to the driver immediately).  When the
    # pipelined headline follows, this early line gets a " [classic]"
    # metric suffix so a first-match parser can never record it AS the
    # headline; under --classic it IS the headline and keeps the name.
    emit(
        1.0 / dt_classic,
        metric=metric_name if args.classic else metric_name + " [classic]",
        driver="classic",
    )

    extra = {}
    if not args.classic:
        # The device-resident pipelined driver (magicsoup_tpu/stepper.py):
        # same canonical workload, selection and placement on device, host
        # genome bookkeeping replayed asynchronously — no device->host
        # fetch on the step critical path.  This is the headline number;
        # the serial loop above is reported alongside as
        # classic_steps_per_s.
        st = ms.PipelinedStepper(
            world,
            mol_name="ATP",
            kill_below=1.0,
            divide_above=5.0,
            divide_cost=4.0,
            target_cells=args.n_cells,
            genome_size=args.genome_size,
            lag="auto" if args.lag == "auto" else int(args.lag),
            megastep=args.megastep,
        )
        for _ in range(max(args.warmup, 3)):
            st.step()
        st.drain()
        st.wait_warm()
        st.trace.clear()
        t0 = time.perf_counter()
        n_pipe = args.steps * 4
        for _ in range(n_pipe):
            st.step()
        st.drain()  # all outputs arrived + replayed
        # each dispatch is args.megastep fused device steps — normalize
        # to SIMULATION steps so K>1 numbers compare against K=1 directly
        dt_pipe = (time.perf_counter() - t0) / (n_pipe * args.megastep)
        trace = list(st.trace)
        st.flush()
        extra = {
            "classic_steps_per_s": round(1.0 / dt, 4),
            "pipelined_steps_per_s": round(1.0 / dt_pipe, 4),
            "megastep": args.megastep,
            "pipeline_stats": {
                k: int(v) for k, v in st.stats.items()
            },
        }
        if trace:
            # per-step diagnosis to stderr: where a slow window's time
            # went (cold compiles / blocked fetches / dispatch overhead)
            if len(trace) < n_pipe:
                # the stepper bounds its trace ring; sums below would
                # silently underreport a window longer than the ring
                sys.stderr.write(
                    f"[trace] WARNING: trace holds {len(trace)} of "
                    f"{n_pipe} measured steps; sums are partial\n"
                )
            tt = sorted(t["t"] for t in trace)
            mid = tt[len(tt) // 2]
            p90 = tt[int(len(tt) * 0.9)]
            occ = [t["alive"] / t["q"] for t in trace if "alive" in t]
            occ_mean = sum(occ) / len(occ) if occ else float("nan")
            sys.stderr.write(
                f"[trace] steps={len(trace)} t_med={mid*1e3:.1f}ms"
                f" t_p90={p90*1e3:.1f}ms t_max={tt[-1]*1e3:.1f}ms"
                f" cold_dispatches={sum(t['cold'] for t in trace)}"
                f" compactions={sum(t['compact'] for t in trace)}"
                f" fetch_s={sum(t['fetch'] for t in trace):.2f}"
                f" dispatch_s={sum(t['dispatch'] for t in trace):.2f}"
                f" total_s={sum(t['t'] for t in trace):.2f}"
                f" occupancy={occ_mean:.2f}\n"
            )
            slow = [t for t in trace if t["t"] > 3 * mid]
            for t in slow[:8]:
                sys.stderr.write(f"[trace-slow] {t}\n")
        # headline = the faster driver of the same workload (both rates
        # are reported and "driver" records which one won, so cross-run
        # comparisons stay interpretable; the pipelined driver exists to
        # beat the serial loop but must never hide a regression behind it)
        dt = min(dt_pipe, dt)
        extra["driver"] = "pipelined" if dt_pipe <= dt_classic else "classic"

    if extra:
        emit(1.0 / dt, **extra)


def _looks_transient(stderr: str) -> bool:
    return any(m in stderr for m in _TRANSIENT_MARKERS)


# keep in sync with LOCK in scripts/capture_tpu_numbers.sh (the capture
# script wraps its non-bench harnesses in the same flock).  Tests point
# MAGICSOUP_BENCH_LOCK_PATH at a private file so harness contract tests
# can never contend with (or stall) a live capture on the global lock.
_ACCEL_LOCK_PATH = os.environ.get(
    "MAGICSOUP_BENCH_LOCK_PATH", "/tmp/magicsoup_tpu_accel.lock"
)


def _acquire_accel_lock(max_wait_s: float, platform: str | None = None):
    """One accelerator job at a time: concurrent benchmarks through the
    shared chip+tunnel contaminate each other's timings (the round-3
    windows showed a single fetch storm doubling another job's step
    times).  Returns the held lock file (kept open for the process
    lifetime — flock releases automatically when the process dies, so a
    crashed holder can never wedge later runs) and raises TimeoutError
    after ``max_wait_s`` of contention.  CPU-pinned smoke runs return
    None without locking: they touch no shared accelerator and must be
    parallelizable in CI; any other platform pin still names a shared
    accelerator and locks like the unpinned path.  ``platform``
    overrides the env-derived pin for harnesses with their own flag
    (performance/readme_slice.py)."""
    if (_PLATFORM if platform is None else platform) == "cpu":
        return None
    if os.environ.get("MAGICSOUP_BENCH_LOCK_HELD") == "1":
        # an enclosing capture script already holds the flock around this
        # process (scripts/capture_tpu_numbers.sh) — taking it again here
        # would self-deadlock
        return None
    import fcntl

    f = open(_ACCEL_LOCK_PATH, "w")
    deadline = time.monotonic() + max_wait_s
    while True:
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return f
        except BlockingIOError:
            # EWOULDBLOCK = genuine contention; any other OSError (ENOLCK
            # on odd mounts, bad fd) propagates as a real error instead
            # of masquerading as "held by another process"
            if time.monotonic() >= deadline:
                f.close()
                raise TimeoutError(
                    f"accelerator lock {_ACCEL_LOCK_PATH} held by another"
                    f" process for > {max_wait_s:.0f}s"
                )
            time.sleep(5.0)


def _is_result_line(line: str) -> bool:
    line = line.strip()
    if not line.startswith("{"):
        return False
    try:
        d = json.loads(line)
    except ValueError:
        return False
    return isinstance(d, dict) and "value" in d and "metric" in d


def _run_attempt(
    child_cmd: list[str],
    timeout_s: float,
    state: dict,
    ready_timeout_s: float = 90.0,
) -> tuple[int, str]:
    """Run one measurement child, forwarding every JSON result line to our
    stdout THE MOMENT it appears (sets state['printed'];
    state['headline'] when the line carries the pipelined rate) so a later
    hang, crash or driver kill cannot erase an already-measured number.

    The attempt doubles as the backend probe: a half-dead tunnel hangs the
    child inside its first jax call with zero output, so a child that has
    neither printed the "[bench-child] backend ready" marker nor a result
    line within ``ready_timeout_s`` is killed (returncode -2, retryable).
    Once the marker appears only the full ``timeout_s`` applies — remote
    compiles may legitimately take minutes.  Returncode -1 means the full
    attempt timed out."""
    proc = subprocess.Popen(
        child_cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    # visible to the SIGTERM handler: an orphaned child would keep the
    # one-job-at-a-time accelerator busy after the parent dies
    state["proc"] = proc
    stderr_chunks: list[str] = []
    ready = threading.Event()

    def _read_out() -> None:
        for line in proc.stdout:
            if _is_result_line(line):
                print(line.rstrip("\n"), flush=True)
                state["printed"] = True
                ready.set()
                if "pipelined_steps_per_s" in line:
                    state["headline"] = True

    def _read_err() -> None:
        # drain continuously: a full stderr pipe would deadlock the child
        for line in proc.stderr:
            if "[bench-child] backend ready" in line:
                ready.set()
            stderr_chunks.append(line)

    t_out = threading.Thread(target=_read_out, daemon=True)
    t_err = threading.Thread(target=_read_err, daemon=True)
    t_out.start()
    t_err.start()
    t_start = time.monotonic()
    rc: int | None = None
    while rc is None:
        try:
            rc = proc.wait(timeout=1.0)
        except subprocess.TimeoutExpired:
            elapsed = time.monotonic() - t_start
            if not ready.is_set() and elapsed > ready_timeout_s:
                proc.kill()
                proc.wait()
                rc = -2
            elif elapsed > timeout_s:
                proc.kill()
                proc.wait()
                rc = -1
    state["proc"] = None
    t_out.join(timeout=10)
    t_err.join(timeout=10)
    return rc, "".join(stderr_chunks)[-4000:]


# fallbacks for the preset-controlled args (parser defaults are None so
# explicit flags are detectable); applied after any --config preset
_ARG_FALLBACKS = {
    "n_cells": 10_000,
    "map_size": 128,
    "chemistry": "wood_ljungdahl",
}


def _apply_config(args: argparse.Namespace) -> None:
    """Resolve preset-controlled args: explicit flag > --config preset >
    fallback.  `--config rich --n-cells 80` means a small rich-chemistry
    run, and `--config 40k --n-cells 10000` honors the explicit 10k —
    the parser's None default makes 'explicitly set to the fallback
    value' distinguishable from 'omitted'."""
    preset = CONFIGS[args.config] if args.config is not None else {}
    for key, fallback in _ARG_FALLBACKS.items():
        if getattr(args, key) is None:
            setattr(args, key, preset.get(key, fallback))


def main() -> None:
    ap = _build_parser()
    args = ap.parse_args()
    _apply_config(args)
    if args.det and args.pallas:
        ap.error(
            "--det and --pallas are mutually exclusive: the Pallas kernel"
            " has no bit-reproducible variant"
        )
    if args._child:
        _child_main(args)
        return

    # 20 min default: deliberately WELL UNDER the driver's observed
    # ~30 min kill window (BENCH_r02/r03 died at rc=124 with the old
    # 30 min budget before the structured-failure line could print)
    budget_s = float(os.environ.get("MAGICSOUP_BENCH_RETRY_BUDGET", "1200"))
    attempt_timeout_s = float(
        os.environ.get("MAGICSOUP_BENCH_ATTEMPT_TIMEOUT", "900")
    )
    child_cmd = [sys.executable, str(Path(__file__).resolve()), "--_child"] + [
        a for a in sys.argv[1:]
    ]

    ready_timeout_s = float(
        os.environ.get("MAGICSOUP_BENCH_READY_TIMEOUT", "90")
    )

    deadline = time.monotonic() + budget_s
    state = {"printed": False, "headline": False, "last_err": "", "proc": None}
    mode = " [deterministic]" if args.det else (" [pallas]" if args.pallas else "")

    def _fail_json() -> str:
        return json.dumps(
            {
                "metric": (
                    f"sim steps/sec ({args.n_cells} cells, "
                    f"{args.map_size}x{args.map_size} map, "
                    f"{args.chemistry.replace('_', '-')} "
                    f"run_simulation workload){mode}"
                ),
                "value": 0.0,
                "unit": "steps/s",
                "vs_baseline": 0.0,
                "error": state["last_err"][-1500:],
                "attempts": state.get("attempt", 0),
            }
        )

    def _on_term(signum, frame):
        # the driver is killing us: leave a parseable line behind unless a
        # real result already went out, and never orphan a measurement
        # child (it would keep the one-job-at-a-time accelerator busy)
        proc = state.get("proc")
        if proc is not None:
            try:
                proc.kill()
            except OSError:
                pass
        if not state["printed"]:
            state["last_err"] = (
                f"killed by signal {signum}; last: {state['last_err']}"
            )
            print(_fail_json(), flush=True)
        os._exit(1)

    signal.signal(signal.SIGTERM, _on_term)

    # serialize against any other real-accelerator benchmark (e.g. the
    # automated capture script firing in the same tunnel window); wait at
    # most half the budget so the structured failure line still prints
    try:
        accel_lock = _acquire_accel_lock(max_wait_s=min(600.0, budget_s / 2))
    except (TimeoutError, OSError) as exc:
        # a lock-file error (unwritable /tmp, foreign-owner file under a
        # sticky bit, ENOLCK) must still yield the structured failure
        # line, never a bare traceback
        state["last_err"] = f"accelerator lock unavailable: {exc}"
        print(_fail_json(), flush=True)
        sys.exit(1)
    _ = accel_lock  # held for process lifetime; flock releases on exit

    backoff_s = 15.0
    attempt = 0
    headline_retries_left = 1
    while True:
        attempt += 1
        state["attempt"] = attempt
        remaining = deadline - time.monotonic()
        if remaining < 10:
            break
        # attempt #1 IS the measurement (no separate probe): in a short
        # tunnel window every second counts, and the ready watchdog inside
        # _run_attempt fails a dead backend as fast as a probe would.  An
        # attempt may never outlive the overall budget — a hang is killed
        # in time for the structured failure line to print.
        rc, err_tail = _run_attempt(
            child_cmd,
            min(attempt_timeout_s, remaining),
            state,
            ready_timeout_s=min(ready_timeout_s, remaining),
        )
        if state["printed"]:
            # at least one measured number reached stdout
            if state["headline"] or args.classic or rc == 0:
                sys.stderr.write(err_tail)
                if rc != 0:
                    sys.stderr.write(
                        f"\n[bench] note: child rc={rc} after a result line"
                        " was already emitted\n"
                    )
                return
            # the classic line went out but the pipelined phase died
            # before the headline line: a classic-only record must not
            # silently stand in for the headline (ADVICE r04).  A
            # TRANSIENT failure (tunnel blip / hang) goes through the
            # normal backoff loop without consuming the retry — the
            # budget bounds it; only a deterministic crash consumes the
            # single headline retry (compiles are cached, so it is cheap).
            transient = rc in (-1, -2) or _looks_transient(err_tail)
            if transient and deadline - time.monotonic() > backoff_s + 60:
                sys.stderr.write(
                    err_tail
                    + f"\n[bench] transient failure (rc={rc}) after the"
                    f" classic line, before the headline; backing off"
                    f" {backoff_s:.0f}s and retrying for the headline\n"
                )
                time.sleep(backoff_s)
                backoff_s = min(backoff_s * 2, 120.0)
                continue
            if (
                not transient
                and headline_retries_left > 0
                and deadline - time.monotonic() > 60
            ):
                headline_retries_left -= 1
                sys.stderr.write(
                    err_tail
                    + f"\n[bench] child rc={rc} after the classic line but"
                    " before the headline (pipelined) line; retrying once\n"
                )
                continue
            sys.stderr.write(
                err_tail
                + f"\n[bench] note: child rc={rc}; the ' [classic]' line is"
                " the only measured result (headline retries/budget"
                " exhausted)\n"
            )
            return
        state["last_err"] = (
            f"backend not ready (> {min(ready_timeout_s, remaining):.0f}s, "
            "no jax.devices() answer)"
            if rc == -2
            else f"bench attempt hung (> {min(attempt_timeout_s, remaining):.0f}s)"
            if rc == -1
            else err_tail or f"rc={rc}, no output"
        )
        if rc == 0:
            # exited cleanly yet printed no result line: deterministic
            # bug, retrying cannot help
            state["last_err"] = (
                "child exited 0 without a result line; stderr: "
                + state["last_err"]
            )
            break
        if rc not in (-1, -2) and not _looks_transient(state["last_err"]):
            break  # a real bug; retrying won't help

        if time.monotonic() + backoff_s > deadline:
            break
        sys.stderr.write(
            f"[bench] attempt {attempt} failed (transient), retrying in "
            f"{backoff_s:.0f}s: "
            f"{state['last_err'].splitlines()[-1] if state['last_err'] else '?'}\n"
        )
        time.sleep(backoff_s)
        backoff_s = min(backoff_s * 2, 120.0)

    print(_fail_json(), flush=True)
    sys.exit(1)


if __name__ == "__main__":
    main()
