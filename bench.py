"""
Headline benchmark: sim steps/sec at 10k cells on a 128x128 map running the
reference's realistic workload (`performance/run_simulation.py:43-113`):
spawn top-up, enzymatic_activity, ATP-threshold kill and divide,
recombinate, mutate, degrade+diffuse+lifetimes.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "steps/s", "vs_baseline": N}

Baseline: the reference's CUDA numbers (EC2 GPU, 2023-12-19,
`performance/run_simulation.py:20`) are 0.03 s/step at 1k cells and
0.30 s/step at 40k cells; linear interpolation in cell count gives
~0.0923 s/step at 10k cells -> 10.83 steps/s.  `vs_baseline` > 1 means
faster than the reference on its own headline workload.

Run on whatever accelerator JAX finds (the driver provides a TPU chip); do
not pin a platform here.
"""
import argparse
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

BASELINE_S_PER_STEP = 0.03 + (0.30 - 0.03) * (10_000 - 1_000) / (40_000 - 1_000)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-cells", type=int, default=10_000)
    ap.add_argument("--map-size", type=int, default=128)
    ap.add_argument("--genome-size", type=int, default=500)
    ap.add_argument("--warmup", type=int, default=8)
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--pallas",
        action="store_true",
        help="use the VMEM-tiled Pallas integrator kernel",
    )
    args = ap.parse_args()

    import magicsoup_tpu as ms
    from magicsoup_tpu.examples.wood_ljungdahl import CHEMISTRY
    from magicsoup_tpu.util import random_genome

    sys.path.insert(0, str(Path(__file__).resolve().parent / "performance"))
    from workload import sim_step

    rng = random.Random(args.seed)
    world = ms.World(
        chemistry=CHEMISTRY,
        map_size=args.map_size,
        seed=args.seed,
        use_pallas=args.pallas,
    )
    world.spawn_cells(
        [random_genome(s=args.genome_size, rng=rng) for _ in range(args.n_cells)]
    )
    atp = CHEMISTRY.molname_2_idx["ATP"]

    def step(sync: bool) -> None:
        sim_step(
            world,
            rng,
            n_cells=args.n_cells,
            genome_size=args.genome_size,
            atp_idx=atp,
            sync=sync,
        )

    for _ in range(args.warmup):
        step(sync=True)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        # async steps: each step's selection fetch syncs the prior one
        step(sync=False)
    import jax

    jax.block_until_ready((world._molecule_map, world._cell_molecules))
    dt = (time.perf_counter() - t0) / args.steps

    steps_per_s = 1.0 / dt
    print(
        json.dumps(
            {
                "metric": (
                    f"sim steps/sec ({args.n_cells} cells, "
                    f"{args.map_size}x{args.map_size} map, wood-ljungdahl "
                    "run_simulation workload)"
                ),
                "value": round(steps_per_s, 4),
                "unit": "steps/s",
                "vs_baseline": round(steps_per_s * BASELINE_S_PER_STEP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
